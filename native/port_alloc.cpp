// _nomad_native: C++ hot-path helpers for the host scheduling plane.
//
// The reference implements its entire runtime in Go; our host plane is
// Python, and profiling shows the per-placement dynamic-port assignment
// (nomad_tpu/structs/network.py assign_network -- the sequential, stateful
// part of placement that cannot move to the TPU) dominating host time at
// 10k-node scale.  This module implements that inner loop in C++ against
// CPython sets, plus a bulk random-port reservation primitive.
//
// Built as a CPython extension (no pybind11; plain C API) by
// native/build.py; nomad_tpu falls back to the pure-Python path when the
// extension is unavailable.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <random>

namespace {

thread_local std::mt19937 rng{std::random_device{}()};

// assign_ports(used: set[int], reserved: sequence[int], n_dynamic: int,
//              min_port: int, max_port: int, attempts: int)
//   -> list[int] | None
//
// Mirrors NetworkIndex.assign_network's port logic exactly: reserved ports
// must not collide with `used`; each dynamic port is picked uniformly from
// [min_port, max_port) avoiding `used` and already-picked ports, with a
// bounded number of attempts.  Returns the full offer port list
// (reserved + dynamic) or None on failure.  `used` is NOT mutated.
PyObject* assign_ports(PyObject*, PyObject* args) {
  PyObject* used;
  PyObject* reserved;
  Py_ssize_t n_dynamic;
  long min_port, max_port;
  Py_ssize_t attempts;
  if (!PyArg_ParseTuple(args, "OOnlln", &used, &reserved, &n_dynamic,
                        &min_port, &max_port, &attempts)) {
    return nullptr;
  }
  if (!PySet_Check(used)) {
    PyErr_SetString(PyExc_TypeError, "used must be a set");
    return nullptr;
  }

  PyObject* reserved_fast =
      PySequence_Fast(reserved, "reserved must be a sequence");
  if (reserved_fast == nullptr) return nullptr;
  Py_ssize_t n_reserved = PySequence_Fast_GET_SIZE(reserved_fast);

  PyObject* out = PyList_New(0);
  if (out == nullptr) {
    Py_DECREF(reserved_fast);
    return nullptr;
  }

  // Reserved ports: collision -> None.
  for (Py_ssize_t i = 0; i < n_reserved; i++) {
    PyObject* port = PySequence_Fast_GET_ITEM(reserved_fast, i);
    int hit = PySet_Contains(used, port);
    if (hit < 0) goto fail;
    if (hit) {
      Py_DECREF(reserved_fast);
      Py_DECREF(out);
      Py_RETURN_NONE;
    }
    if (PyList_Append(out, port) < 0) goto fail;
  }

  {
    std::uniform_int_distribution<long> dist(min_port, max_port - 1);
    for (Py_ssize_t d = 0; d < n_dynamic; d++) {
      bool placed = false;
      for (Py_ssize_t a = 0; a < attempts; a++) {
        long candidate = dist(rng);
        PyObject* port = PyLong_FromLong(candidate);
        if (port == nullptr) goto fail;
        int hit = PySet_Contains(used, port);
        if (hit < 0) {
          Py_DECREF(port);
          goto fail;
        }
        if (!hit) {
          // Also avoid ports already picked into this offer.
          int dup = PySequence_Contains(out, port);
          if (dup < 0) {
            Py_DECREF(port);
            goto fail;
          }
          if (!dup) {
            int rc = PyList_Append(out, port);
            Py_DECREF(port);
            if (rc < 0) goto fail;
            placed = true;
            break;
          }
        }
        Py_DECREF(port);
      }
      if (!placed) {
        Py_DECREF(reserved_fast);
        Py_DECREF(out);
        Py_RETURN_NONE;
      }
    }
  }

  Py_DECREF(reserved_fast);
  return out;

fail:
  Py_DECREF(reserved_fast);
  Py_DECREF(out);
  return nullptr;
}

// add_all(used: set[int], ports: sequence[int]) -> bool collide
PyObject* add_all(PyObject*, PyObject* args) {
  PyObject* used;
  PyObject* ports;
  if (!PyArg_ParseTuple(args, "OO", &used, &ports)) return nullptr;
  if (!PySet_Check(used)) {
    PyErr_SetString(PyExc_TypeError, "used must be a set");
    return nullptr;
  }
  PyObject* fast = PySequence_Fast(ports, "ports must be a sequence");
  if (fast == nullptr) return nullptr;
  bool collide = false;
  for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(fast); i++) {
    PyObject* port = PySequence_Fast_GET_ITEM(fast, i);
    int hit = PySet_Contains(used, port);
    if (hit < 0) {
      Py_DECREF(fast);
      return nullptr;
    }
    if (hit) {
      collide = true;
    } else if (PySet_Add(used, port) < 0) {
      Py_DECREF(fast);
      return nullptr;
    }
  }
  Py_DECREF(fast);
  return PyBool_FromLong(collide);
}

PyMethodDef methods[] = {
    {"assign_ports", assign_ports, METH_VARARGS,
     "Assign reserved + dynamic ports against a used-port set."},
    {"add_all", add_all, METH_VARARGS,
     "Add ports to a used-port set; returns True on any collision."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_nomad_native",
    "C++ hot-path helpers for the host scheduling plane.", -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__nomad_native(void) {
  return PyModule_Create(&module);
}
